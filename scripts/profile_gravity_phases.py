"""Phase-by-phase timing of the dense MAC gravity solve at 1M (VERDICT
r4 #3: measure, then fix). Re-times compute_gravity's internal stages as
incremental jitted programs: multipoles / accept sweep / +downsweep /
+sort-compaction / +M2P gather+eval / full solve — the deltas localize
the 975 ms (round-4 measurement, tb=256).

Usage: [N_PARTS=1000000] python scripts/profile_gravity_phases.py

Recording the results (chip-harvest protocol, docs/NEXT.md round 8):
set TRACE_DIR=/path to also capture a jax.profiler trace of the full
solve — the production gravity stages carry sphexa/gravity-upsweep/
-mac/-m2p/-p2p named scopes, so `sphexa-telemetry trace $TRACE_DIR`
renders the same phase split from device-op metadata (the durable,
diffable record; the incremental re-timings below remain the
fine-grained cross-check).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from sphexa_tpu.gravity import multipole as mp
from sphexa_tpu.gravity.traversal import (
    GravityConfig, compute_gravity, compute_multipoles,
    estimate_gravity_caps,
)
from sphexa_tpu.gravity.tree import build_gravity_tree
from sphexa_tpu.init.plummer import sample_plummer as plummer
from sphexa_tpu.sfc.box import BoundaryType, Box
from sphexa_tpu.sfc.keys import compute_sfc_keys

N = int(os.environ.get("N_PARTS", "1000000"))
THETA = float(os.environ.get("THETA", "0.5"))
TB = int(os.environ.get("TB", "256"))


def timed(fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    out = fn(*args)  # discard first post-compile batch (axon)
    float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / reps, out


def main():
    x, y, z, m = plummer(N)
    r = float(np.max(np.abs(np.stack([x, y, z])))) * 1.001
    box = Box.create(-r, r, boundary=BoundaryType.open)
    keys = np.asarray(compute_sfc_keys(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), box))
    order = np.argsort(keys)
    xs, ys, zs, ms = (jnp.asarray(a[order]) for a in (x, y, z, m))
    skeys = jnp.asarray(keys[order])
    gtree, meta = build_gravity_tree(keys[order], bucket_size=64)
    hs = jnp.full_like(xs, 1e-3)
    num_n = meta.num_nodes
    print(f"N={N} nodes={num_n} leaves={meta.num_leaves} tb={TB}")

    base = GravityConfig(theta=THETA, bucket_size=64, G=1.0,
                         target_block=TB,
                         blocks_per_chunk=max(4, 2048 // TB),
                         use_pallas=jax.default_backend() == "tpu")
    cfg = estimate_gravity_caps(xs, ys, zs, ms, skeys, box, gtree, meta,
                                base, margin=1.6)
    print(f"caps: m2p={cfg.m2p_cap} p2p={cfg.p2p_cap} leaf={cfg.leaf_cap}")

    t_mp, mpc = timed(
        jax.jit(lambda *a: compute_multipoles(*a, gtree, meta, order=0)),
        xs, ys, zs, ms, skeys)
    print(f"multipoles      : {t_mp*1e3:8.1f} ms")
    node_mass, node_com, node_q, edges = mpc
    valid = node_mass > 0.0

    lengths = box.lengths
    lo = jnp.stack([box.lo[0], box.lo[1], box.lo[2]])
    geo_center = lo[None, :] + gtree.center_frac * lengths[None, :]
    geo_size = gtree.halfsize_frac[:, None] * lengths[None, :]
    l_node = 2.0 * jnp.max(geo_size, axis=1)
    s_off = jnp.sqrt(jnp.sum((node_com - geo_center) ** 2, axis=1))
    # monotone MAC preamble (mirrors compute_gravity)
    smax = jnp.where(valid, s_off, 0.0)
    BIG = jnp.float32(1e15)
    com_lo = jnp.where(valid[:, None], node_com, BIG)
    com_hi = jnp.where(valid[:, None], node_com, -BIG)
    for s_, e_ in reversed(meta.level_ranges[1:]):
        par_ = gtree.parent[s_:e_]
        smax = smax.at[par_].max(smax[s_:e_])
        com_lo = com_lo.at[par_].min(com_lo[s_:e_])
        com_hi = com_hi.at[par_].max(com_hi[s_:e_])
    ccenter = jnp.where(valid[:, None], 0.5 * (com_lo + com_hi), BIG)
    chalf = jnp.where(valid[:, None],
                      jnp.maximum(0.5 * (com_hi - com_lo), 0.0), 0.0)
    mac2 = (l_node / cfg.theta + smax) ** 2
    self_parent = gtree.parent == jnp.arange(num_n,
                                             dtype=gtree.parent.dtype)

    blk = cfg.target_block
    num_blocks = -(-N // blk)
    chunk = cfg.blocks_per_chunk
    num_chunks = -(-num_blocks // chunk)
    idx = jnp.arange(num_chunks * chunk * blk, dtype=jnp.int32)
    idx = jnp.minimum(idx, N - 1).reshape(num_chunks, chunk, blk)

    node_packed = jnp.concatenate(
        [node_com, node_q, node_mass[:, None],
         jnp.zeros((num_n, 1), node_com.dtype)], axis=1)

    def _bbox(tx, ty, tz):
        bc = jnp.stack([(jnp.max(tx) + jnp.min(tx)) * 0.5,
                        (jnp.max(ty) + jnp.min(ty)) * 0.5,
                        (jnp.max(tz) + jnp.min(tz)) * 0.5])
        bs = jnp.stack([(jnp.max(tx) - jnp.min(tx)) * 0.5,
                        (jnp.max(ty) - jnp.min(ty)) * 0.5,
                        (jnp.max(tz) - jnp.min(tz)) * 0.5])
        return bc, bs

    def _accept(bc, bs, gc, gs, m2):
        d = jnp.maximum(jnp.abs(bc[None, :] - gc) - bs[None, :] - gs, 0.0)
        return jnp.sum(d * d, axis=1) >= m2

    def block_phase(bi, phase):
        tx, ty, tz = x_[bi], y_[bi], z_[bi]
        bc, bs = _bbox(tx, ty, tz)
        accept = valid & _accept(bc, bs, ccenter, chalf, mac2)
        if phase == 1:
            return jnp.sum(accept)
        # monotone MAC: one parent gather replaces the level downsweep
        anc = jnp.where(self_parent, False, accept[gtree.parent])
        m2p_mask = accept & ~anc
        p2p_mask = gtree.is_leaf & valid & ~accept
        if phase == 2:
            return jnp.sum(m2p_mask) + jnp.sum(p2p_mask)
        m2p_n = jnp.sum(m2p_mask)
        cls = jnp.where(m2p_mask, 0, jnp.where(p2p_mask, 1, 2))
        nbits = max(1, int(np.ceil(np.log2(max(num_n, 2)))))
        iota_k = jnp.arange(num_n, dtype=jnp.int32)
        ks = jnp.sort((cls.astype(jnp.int32) << nbits) | iota_k)
        order_all = ks & jnp.int32((1 << nbits) - 1)
        cls_sorted = ks >> nbits
        padn = max(cfg.m2p_cap, cfg.p2p_cap)
        order_all = jnp.concatenate(
            [order_all, jnp.full((padn,), num_n - 1, order_all.dtype)])
        cls_sorted = jnp.concatenate(
            [cls_sorted, jnp.full((padn,), 2, cls_sorted.dtype)])
        order_m = jnp.minimum(order_all[: cfg.m2p_cap], num_n - 1)
        m2p_ok = cls_sorted[: cfg.m2p_cap] == 0
        if phase == 3:
            return jnp.sum(order_m) + jnp.sum(m2p_ok) + m2p_n
        nd = node_packed[order_m]
        ax, ay, az, phi = mp.m2p(
            tx, ty, tz, nd[:, 0:3], nd[:, 3:10], nd[:, 10], m2p_ok)
        return jnp.sum(ax) + jnp.sum(ay) + jnp.sum(az)

    x_, y_, z_ = xs, ys, zs

    def make(phase):
        def run():
            def one_chunk(bidx):
                return jax.vmap(lambda b: block_phase(b, phase))(bidx)
            return jax.lax.map(one_chunk, idx)
        return jax.jit(run)

    labels = {1: "accept sweep    ", 2: "+downsweep      ",
              3: "+sort+compaction", 4: "+M2P gather+eval"}
    prev = 0.0
    for phase in (1, 2, 3, 4):
        t, _ = timed(make(phase))
        print(f"{labels[phase]}: {t*1e3:8.1f} ms   (delta "
              f"{(t-prev)*1e3:+8.1f} ms)")
        prev = t

    t_full, out = timed(
        jax.jit(lambda: compute_gravity(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, cfg,
            mp_cache=mpc)))
    d = {k: float(v) for k, v in out[4].items()}
    print(f"full solve      : {t_full*1e3:8.1f} ms   "
          f"({N/t_full/1e6:.2f}M parts/s, m2p_max={int(d['m2p_max'])} "
          f"p2p_max={int(d['p2p_max'])})")

    # compaction-mode comparison (ISSUE 1): the flat per-block sort vs
    # the bitmask-rank kernel, flat and hierarchical. compact_width is
    # the per-block candidate width of the list materialization — the
    # op-count/complexity proxy recorded when no chip is available
    # (blocks x width ~ hot-path compaction work; the sort pays an extra
    # log-factor on top of its width).
    import dataclasses

    sf = int(os.environ.get("SUPER", "8"))
    variants = [("sort     sf=0 ", cfg)]
    cfg_b0 = dataclasses.replace(cfg, compaction="bitmask", super_factor=0)
    variants.append(("bitmask  sf=0 ", cfg_b0))
    base_h = dataclasses.replace(base, compaction="bitmask", super_factor=sf)
    cfg_h = estimate_gravity_caps(xs, ys, zs, ms, skeys, box, gtree, meta,
                                  base_h, margin=1.6)
    variants.append((f"bitmask  sf={sf}", cfg_h))
    for tag, c in variants:
        t, o = timed(jax.jit(lambda c=c: compute_gravity(
            xs, ys, zs, ms, hs, skeys, box, gtree, meta, c, mp_cache=mpc)))
        dd = {k: float(v) for k, v in o[4].items()}
        print(f"solve [{tag}]: {t*1e3:8.1f} ms   compact_width="
              f"{int(dd['compact_width'])} c_max={int(dd['c_max'])} "
              f"m2p_max={int(dd['m2p_max'])}")

    # the durable record: capture the tuned solve under the profiler and
    # attribute by the in-graph gravity phases (sphexa-telemetry trace)
    trace_dir = os.environ.get("TRACE_DIR")
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        for _ in range(2):
            jax.block_until_ready(compute_gravity(
                xs, ys, zs, ms, hs, skeys, box, gtree, meta, cfg,
                mp_cache=mpc))
        jax.profiler.stop_trace()
        print(f"trace -> {trace_dir}  (render: sphexa-telemetry trace "
              f"{trace_dir})")


if __name__ == "__main__":
    main()
