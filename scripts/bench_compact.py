"""Microbench the list-walk engine's per-chunk lane-compaction cost on
real TPU hardware.

The walk engine (sph/pallas_pairs.py group_pair_engine_lists) pays a
fixed per-marked-chunk cost: lane gather (take_along_axis), image-shift
add, staged-index insert, and two staging-window selects. From the
measured op times (momentum walk 123 ms = 27 chunks compaction + 9
chunks math at 100^3) that fixed cost is ~145 ns/chunk — as expensive as
the 60-op momentum math itself, and the reason cheap ops (density/IAD)
stay on skip-streaming. This bench isolates the candidates:

  loop      — DMA-less chunk loop, accumulate one row (floor)
  gather    — + take_along_axis lane gather on the (8, 128) chunk
  onehot    — + MXU permute: build (128,128) one-hot from the index row
              in-kernel, chunk @ P (same result as gather)
  full      — the engine's whole compaction block (gather variant)
  fullmxu   — the whole block with the one-hot permute instead

Timing: dependent-scalar barrier, first batch discarded (docs/NEXT.md).
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def make_kernel(variant: str, S: int, nf: int):
    def kernel(gidx_ref, cnt_r, fill_r, data_ref, out_ref, stage):
        lane_f = jax.lax.broadcasted_iota(jnp.int32, (nf, 128), 1)
        subl = jax.lax.broadcasted_iota(jnp.int32, (nf, 128), 0)
        iota_r = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)

        def body(t, acc):
            chunk = data_ref[0, t]
            cnt = cnt_r[0, 0, t]
            fill = fill_r[0, 0, t]
            gi_row = gidx_ref[0, t][None, :]  # (1, 128)
            if variant == "loop":
                acc = acc + chunk
            elif variant == "gather":
                rolled = jnp.take_along_axis(
                    chunk, jnp.broadcast_to(gi_row, (nf, 128)), axis=1)
                acc = acc + rolled
            elif variant == "onehot":
                P = (iota_r == gi_row).astype(jnp.float32)  # (128,128)
                rolled = jax.lax.dot_general(
                    chunk, P, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc = acc + rolled
            elif variant in ("full", "fullmxu"):
                if variant == "full":
                    rolled = jnp.take_along_axis(
                        chunk, jnp.broadcast_to(gi_row, (nf, 128)), axis=1)
                else:
                    P = (iota_r == gi_row).astype(jnp.float32)
                    rolled = jax.lax.dot_general(
                        chunk, P, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                shift_col = jnp.where(
                    subl[:, :1] == 0, 1.0,
                    jnp.where(subl[:, :1] == 1, 2.0,
                              jnp.where(subl[:, :1] == 2, 3.0, 0.0)))
                rolled = rolled + shift_col
                idx_f = (t * 128 + gi_row).astype(jnp.float32)
                rolled = jnp.where(
                    subl == nf - 1,
                    jnp.broadcast_to(idx_f, rolled.shape), rolled)
                m0 = (lane_f >= fill) & (lane_f < fill + cnt)
                m1 = lane_f < (fill + cnt - 128)
                stage[:, :128] = jnp.where(m0, rolled, stage[:, :128])
                stage[:, 128:] = jnp.where(m1, rolled, stage[:, 128:])
                acc = acc + stage[:, :128]
            return acc

        stage[...] = jnp.zeros((nf, 256), jnp.float32)
        acc = jax.lax.fori_loop(0, S, body, jnp.zeros((nf, 128), jnp.float32))
        out_ref[0] = acc

    return kernel


def run(variant: str, NG: int, S: int, nf: int, reps: int):
    kern = make_kernel(variant, S, nf)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(NG,),
        in_specs=[
            pl.BlockSpec((1, S, 128), lambda g: (g, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda g: (g, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, S), lambda g: (g, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, S, nf, 128), lambda g: (g, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nf, 128), lambda g: (g, 0, 0)),
        scratch_shapes=[pltpu.VMEM((nf, 256), jnp.float32)],
    )
    f = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((NG, nf, 128), jnp.float32),
    )
    rng = np.random.default_rng(0)
    gidx = jnp.asarray(
        np.argsort(rng.random((NG, S, 128)), axis=-1).astype(np.int32))
    cnt = jnp.asarray(rng.integers(0, 129, (NG, 1, S)).astype(np.int32))
    fill = jnp.asarray(rng.integers(0, 128, (NG, 1, S)).astype(np.int32))
    data = jnp.asarray(rng.random((NG, S, nf, 128)).astype(np.float32))

    @jax.jit
    def step(seed):
        # chain a dependency through the data so calls serialize
        out = f(gidx, cnt, fill, data + seed * 1e-12)
        return jnp.sum(out[:, 0, :1])

    s = step(jnp.float32(0))
    float(s)  # compile + discard first batch
    t0 = time.perf_counter()
    v = jnp.float32(0)
    for i in range(reps):
        v = step(v * 1e-30 + i)
    float(v)
    dt = (time.perf_counter() - t0) / reps
    per_chunk = dt / (NG * S) * 1e9
    print(f"{variant:8s}: {dt*1e3:8.2f} ms/call  {per_chunk:7.1f} ns/chunk")
    return per_chunk


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ng", type=int, default=2048)
    ap.add_argument("--slots", type=int, default=27)
    ap.add_argument("--nf", type=int, default=8)
    ap.add_argument("--reps", type=int, default=10)
    args = ap.parse_args()
    print(f"NG={args.ng} S={args.slots} nf={args.nf}")
    base = None
    for v in ("loop", "gather", "onehot", "full", "fullmxu"):
        t = run(v, args.ng, args.slots, args.nf, args.reps)
        if v == "loop":
            base = t
        else:
            print(f"          marginal vs loop: {t - base:7.1f} ns/chunk")


if __name__ == "__main__":
    main()
