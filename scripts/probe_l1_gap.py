"""Bound the Sedov L1_rho gap vs the reference CI (0.166 repo vs 0.138
reference, .jenkins/reframe_ci.py:352) — VERDICT r4 #7.

The ICs are ALREADY matched (init_sedov uses the reference's regular
grid, grid.hpp:90-130 layout; no jitter), so the candidate contributions
are (a) the min-h symmetric pair cutoff (sym_pairs, default on — a
deliberate deviation from momentum_energy_kern.hpp) and (b) f32 vs the
reference's f64 coordinates/accumulations.

Runs the reference config (sedov 50^3, 200 steps) in up to three
flavors and prints each L1:
  default      : sym_pairs on, f32 (the pinned number)
  refparity    : sym_pairs off, f32 (isolates the convention)
  f64          : sym_pairs off, x64 enabled (CPU; isolates precision —
                 pass --f64 to run it, it is minutes-slow off-TPU)

Usage: python scripts/probe_l1_gap.py [--f64]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(tag, sym_pairs, **sim_kw):
    import dataclasses

    from sphexa_tpu.analysis.compare import compute_output_fields, l1_error
    from sphexa_tpu.analysis.sedov import sedov_solution
    from sphexa_tpu.init import init_sedov
    from sphexa_tpu.simulation import Simulation

    state, box, const = init_sedov(50)
    const = dataclasses.replace(const, sym_pairs=sym_pairs)
    sim = Simulation(state, box, const, prop="std", block=8192,
                     check_every=10, **sim_kw)
    t0 = time.perf_counter()
    for _ in range(200):
        sim.step()
    sim.flush()
    fields = compute_output_fields(sim.state, sim.box, sim._cfg)
    t = float(sim.state.ttot)
    sol = sedov_solution(fields["r"], time=t, eblast=1.0,
                         gamma=sim.const.gamma)
    l1 = l1_error(fields["rho"], sol["rho"])
    print(f"{tag:10s}: L1_rho = {l1:.4f}   (t={t:.4e}, "
          f"{time.perf_counter()-t0:.0f}s)", flush=True)
    return l1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--f64", action="store_true")
    args = ap.parse_args()
    if args.f64:
        import jax

        jax.config.update("jax_enable_x64", True)
        # f64 run: the XLA backend path (engine kernels + persistent
        # lists are f32-only)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        run_one("f64-ref", sym_pairs=False, backend="xla",
                use_lists=False)
        return
    run_one("default", sym_pairs=True)
    run_one("refparity", sym_pairs=False)


if __name__ == "__main__":
    main()
