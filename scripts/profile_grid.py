"""Grid-level sweep of the engine's static config (the cell_target /
gap interaction: finer grids fragment runs, aggressive bridging heals
them) — now a thin wrapper over the autotuner's replay harness
(sphexa_tpu/tuning). The harness times the full stepped pipeline with
the sync-free window clock (warmup window absorbs the post-compile
outlier the old min-of-3 loop existed for), emits a schema-v5 ``sweep``
event per candidate into <out>/events.jsonl, and exits nonzero when no
candidate measures cleanly. The hand-rolled jit pipeline + perf_counter
core this script used to duplicate with sweep_engine.py is gone.

Usage: [PROF_SIDE=100] [SWEEP_BUDGET=12] python scripts/profile_grid.py
       [sweep-out-dir]
"""

import os
import sys

from sphexa_tpu.tuning.cli import main

if __name__ == "__main__":
    sys.exit(main([
        "--case", "sedov",
        "--side", os.environ.get("PROF_SIDE", "100"),
        "--backend", "pallas",
        "--knobs", "cell_target,gap,group",
        "--budget", os.environ.get("SWEEP_BUDGET", "12"),
        "--steps", "3", "--warmup", "1",
        "--out", sys.argv[1] if len(sys.argv) > 1 else "profile-grid-out",
        "--format", "json",
    ]))
