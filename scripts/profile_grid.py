"""Grid sweep of the engine's static config: time the FULL fused std
pipeline (sort+prologue+density+iad+momentum) per config, with warmup
(first post-compile batch is a ~1.5x outlier on axon) and min-of-3.

Usage: [PROF_SIDE=100] python scripts/profile_grid.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dataclasses

import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp

SIDE = int(os.environ.get("PROF_SIDE", "100"))
ITERS = 3


def time_config(state, box, const, n, **kw):
    group = kw.pop("group", 64)
    cfg = make_propagator_config(
        state, box, const, block=8192, backend="pallas", group=group, **kw)
    nbr = cfg.nbr

    @jax.jit
    def pipe(x, y, z, h, m, temp, vx, vy, vz):
        keys = jnp.sort(compute_sfc_keys(x, y, z, box))
        ranges = pp.group_cell_ranges(x, y, z, h, keys, box, nbr)
        rho, nc, occ = pp.pallas_density(
            x, y, z, h, m, keys, box, const, nbr, ranges=ranges)
        p, c = hydro_std.compute_eos_std(temp, rho, const)
        cs, _ = pp.pallas_iad(
            x, y, z, h, m / rho, keys, box, const, nbr, ranges=ranges)
        out = pp.pallas_momentum_energy_std(
            x, y, z, vx, vy, vz, h, m, rho, p, c, *cs,
            keys, box, const, nbr, ranges=ranges)
        return out[0], occ, ranges.ncells, ranges.starts, ranges.lens

    args = (state.x, state.y, state.z, state.h, state.m, state.temp,
            state.vx, state.vy, state.vz)
    out = pipe(*args)
    jax.block_until_ready(out)
    occ = int(out[1])
    tag = (f"ct={kw.get('cell_target', 128):4d} g={group:3d} "
           f"rc={kw.get('run_cap', 1536):4d} gap={kw.get('gap', 384):3d} "
           f"lvl={nbr.level} cap={nbr.cap} win={nbr.window}")
    if occ > nbr.cap:
        print(f"{tag}  OVERFLOW occ={occ}", flush=True)
        return
    # warmup batches
    for _ in range(2):
        out = pipe(*args)
        jax.block_until_ready(out)
        _ = float(jnp.sum(out[0]))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = pipe(*args)
        jax.block_until_ready(out)
        _ = float(jnp.sum(out[0]))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    nrun = float(jnp.mean(out[2].astype(jnp.float32)))
    # streamed 128-lane chunk slots per target
    lanes = float(jnp.sum(
        jnp.ceil((out[3] % 128 + out[4]) / 128.0) * 128)) * group / n
    print(f"{tag}  runs~{nrun:5.1f} lanes/tgt~{lanes:6.0f} "
          f"{best*1e3:8.2f} ms  {n/best/1e6:.2f}M/s", flush=True)


def main():
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)
    for _ in range(2):
        sim.step()
    state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, _, _ = _sort_by_keys(state, box, "hilbert")
    n = state.n

    configs = [
        # baseline
        dict(cell_target=128, group=64, run_cap=1536, gap=384),
        # level-5 grid (ct=32 -> finer cells), gap swept: short runs at
        # level 5 need aggressive bridging to avoid 128-lane fragmentation
        dict(cell_target=32, group=64, run_cap=1536, gap=384),
        dict(cell_target=32, group=32, run_cap=1024, gap=256),
        dict(cell_target=32, group=32, run_cap=1024, gap=128),
        dict(cell_target=32, group=32, run_cap=1536, gap=384),
        dict(cell_target=32, group=64, run_cap=1024, gap=128),
        # level-5, big gap: merge most of the window into ~2 runs
        dict(cell_target=32, group=32, run_cap=2048, gap=512),
    ]
    for kw in configs:
        try:
            time_config(state, box, const, n, **kw)
        except Exception as e:  # noqa
            print(f"{kw} FAILED: {type(e).__name__}: {e}"[:200], flush=True)


if __name__ == "__main__":
    main()
