"""Per-op timing of the std pallas pipeline on the current device.

Usage: [PROF_SIDE=100] [PROF_ARGS='cell_target=128,run_cap=1536,gap=384,group=64']
       python scripts/profile_ops.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.simulation import Simulation, make_propagator_config
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sph import hydro_std
from sphexa_tpu.sph import pallas_pairs as pp

SIDE = int(os.environ.get("PROF_SIDE", "100"))
ITERS = int(os.environ.get("PROF_ITERS", "5"))


def parse_args():
    kw = dict(cell_target=128, run_cap=1536, gap=384, group=64)
    s = os.environ.get("PROF_ARGS", "")
    for part in s.split(","):
        if "=" in part:
            k, v = part.split("=")
            kw[k.strip()] = int(v)
    return kw


def timeit(fn, args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    jax.block_until_ready(out)
    # axon: force real completion with a device_get data dependency
    _ = float(jnp.sum(jax.tree.leaves(out)[0]))
    return (time.perf_counter() - t0) / ITERS


def main():
    kw = parse_args()
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop="std", block=8192)
    for _ in range(2):
        sim.step()
    state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, _, _ = _sort_by_keys(state, box, "hilbert")
    n = state.n

    cfg = make_propagator_config(
        state, box, const, block=8192, backend="pallas", **kw)
    nbr = cfg.nbr
    print(f"n={n} level={nbr.level} cap={nbr.cap} win={nbr.window} "
          f"group={nbr.group} run_cap={nbr.run_cap} gap={nbr.gap}")

    x, y, z, h, m = state.x, state.y, state.z, state.h, state.m
    keys = jnp.sort(compute_sfc_keys(x, y, z, box))

    f_ranges = jax.jit(lambda *a: pp.group_cell_ranges(*a, box, nbr))
    t_pro = timeit(f_ranges, (x, y, z, h, keys))
    ranges = f_ranges(x, y, z, h, keys)
    nrun = float(jnp.mean(ranges.ncells.astype(jnp.float32)))
    lanes = float(jnp.sum(jnp.ceil(
        (ranges.starts % 128 + ranges.lens) / 128.0) * 128)) / n
    print(f"prologue: {t_pro*1e3:8.2f} ms   runs/group~{nrun:.1f} "
          f"chunk-lanes/target~{lanes * nbr.group / 1:.0f}")

    f_sort = jax.jit(lambda x, y, z: jnp.argsort(
        compute_sfc_keys(x, y, z, box)))
    t_sort = timeit(f_sort, (x, y, z))
    print(f"keys+argsort: {t_sort*1e3:8.2f} ms")

    f_den = jax.jit(lambda *a: pp.pallas_density(
        *a, keys, box, const, nbr, ranges=ranges))
    t_den = timeit(f_den, (x, y, z, h, m))
    rho, nc, _ = f_den(x, y, z, h, m)
    print(f"density:  {t_den*1e3:8.2f} ms   <nc>={float(jnp.mean(nc)):.1f}")

    p, c = hydro_std.compute_eos_std(state.temp, rho, const)

    f_iad = jax.jit(lambda *a: pp.pallas_iad(
        *a, keys, box, const, nbr, ranges=ranges))
    t_iad = timeit(f_iad, (x, y, z, h, m / rho))
    cs, _ = f_iad(x, y, z, h, m / rho)
    print(f"iad:      {t_iad*1e3:8.2f} ms")

    f_mom = jax.jit(lambda *a: pp.pallas_momentum_energy_std(
        *a, keys, box, const, nbr, ranges=ranges))
    args_m = (x, y, z, state.vx, state.vy, state.vz, h, m, rho, p, c) + cs
    t_mom = timeit(f_mom, args_m)
    print(f"momentum: {t_mom*1e3:8.2f} ms")

    tot = t_pro + t_sort + t_den + t_iad + t_mom
    print(f"total:    {tot*1e3:8.2f} ms  -> {n/tot/1e6:.2f}M updates/s")


if __name__ == "__main__":
    main()
