#!/usr/bin/env python3
"""2-D slice of a dump field (reference scripts/slice.py).

Usage: python scripts/slice.py dump.h5 [-s STEP] [-f rho] [--axis z]
       [--coord 0.0] [--png out.png]

Selects particles within half a smoothing length of the slicing plane and
prints (or plots with --png) the in-plane scatter colored by the field.
"""

import os
import sys
from argparse import ArgumentParser

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None) -> int:
    ap = ArgumentParser()
    ap.add_argument("file")
    ap.add_argument("-s", "--step", type=int, default=-1)
    ap.add_argument("-f", "--field", default="rho")
    ap.add_argument("--axis", choices=("x", "y", "z"), default="z")
    ap.add_argument("--coord", type=float, default=0.0,
                    help="plane position along --axis")
    ap.add_argument("--png", default=None)
    args = ap.parse_args(argv)

    import h5py

    with h5py.File(args.file, "r") as f:
        steps = sorted(
            (int(k.split("#")[1]) for k in f.keys() if k.startswith("Step#"))
        )
        step = steps[args.step] if args.step < 0 else args.step
        g = f[f"Step#{step}"]
        if args.field not in g:
            print(f"field {args.field!r} not in Step#{step}; available: "
                  f"{sorted(g.keys())}", file=sys.stderr)
            return 1
        data = {k: np.asarray(g[k]) for k in ("x", "y", "z", "h")}
        v = np.asarray(g[args.field])
        t = float(np.asarray(g.attrs.get("time", 0.0)))

    normal = data[args.axis]
    keep = np.abs(normal - args.coord) < 0.5 * data["h"]
    in_plane = [a for a in ("x", "y", "z") if a != args.axis]
    u, w = data[in_plane[0]][keep], data[in_plane[1]][keep]
    vv = v[keep]
    if args.png:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        sc = plt.scatter(u, w, c=vv, s=1.0, cmap="viridis")
        plt.colorbar(sc, label=args.field)
        plt.xlabel(in_plane[0])
        plt.ylabel(in_plane[1])
        plt.title(f"{args.field} slice {args.axis}={args.coord} "
                  f"t={t:.5g} (Step#{step})")
        plt.gca().set_aspect("equal")
        plt.savefig(args.png, dpi=150)
        print(f"wrote {args.png} ({keep.sum()} particles)")
    else:
        print(f"# {args.field} slice {args.axis}={args.coord}, Step#{step}, "
              f"t={t:.6g}, {keep.sum()} particles")
        for a, b, c in zip(u, w, vv):
            print(f"{a:.6g} {b:.6g} {c:.6g}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
