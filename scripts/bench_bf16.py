"""Microbench: Mosaic vector bf16 vs f32 pair-math throughput on (G, 128)
tiles — decides whether the engine's pair kernels should compute in bf16
(NEXT.md lever 2). Measures a momentum-like per-chunk body (W poly, AV,
IAD projections) iterated over a VMEM-resident candidate ring, isolating
VPU arithmetic from DMA.

Usage: python scripts/bench_bf16.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

G = 128
CHUNKS = 32  # VMEM-resident candidate chunks per group
NG = 512     # groups (grid size)
ITERS = 20


def make_kernel(dtype):
    cast = lambda a: a.astype(dtype)

    def kernel(i_ref, j_ref, o_ref, acc1, acc2, acc3, acc4):
        xi = i_ref[0, 0][:, None]
        yi = i_ref[0, 1][:, None]
        zi = i_ref[0, 2][:, None]
        hi = i_ref[0, 3][:, None]
        c1 = cast(i_ref[0, 4][:, None])
        c2 = cast(i_ref[0, 5][:, None])
        c3 = cast(i_ref[0, 6][:, None])
        inv_h2 = cast(1.0 / (hi * hi))
        h4 = 4.0 * hi * hi
        acc1[...] = jnp.zeros((G, 128), jnp.float32)
        acc2[...] = jnp.zeros((G, 128), jnp.float32)
        acc3[...] = jnp.zeros((G, 128), jnp.float32)
        acc4[...] = jnp.zeros((G, 128), jnp.float32)

        def body(c, carry):
            chunk = j_ref[c]  # (8, 128) f32
            jx = chunk[0][None, :]
            jy = chunk[1][None, :]
            jz = chunk[2][None, :]
            mj = cast(chunk[3][None, :])
            vj = cast(chunk[4][None, :])
            # geometry stays f32 (neighbor dx needs the mantissa)
            rx = xi - jx
            ry = yi - jy
            rz = zi - jz
            d2 = rx * rx + ry * ry + rz * rz
            mask = d2 < h4
            # ---- castable pair math (the bf16 candidate zone) ----
            u = cast(d2) * inv_h2
            rxc, ryc, rzc = cast(rx), cast(ry), cast(rz)
            w = u
            for _ in range(7):  # 14 FMA poly eval stand-in
                w = w * u + dtype(0.5)
                w = w * u + dtype(0.25)
            t1 = c1 * rxc + c2 * ryc + c3 * rzc
            t2 = c2 * rxc + c3 * ryc + c1 * rzc
            t3 = c3 * rxc + c1 * ryc + c2 * rzc
            rv = rxc * vj + ryc * vj + rzc * vj
            visc = jnp.where(rv < 0, -rv * w, dtype(0))
            a = mj * w + visc
            e1 = (a * t1 + visc * t2).astype(jnp.float32)
            e2 = (a * t2 + visc * t3).astype(jnp.float32)
            e3 = (a * t3 + visc * t1).astype(jnp.float32)
            e4 = (rv * a).astype(jnp.float32)
            zero = jnp.float32(0)
            acc1[...] = acc1[...] + jnp.where(mask, e1, zero)
            acc2[...] = acc2[...] + jnp.where(mask, e2, zero)
            acc3[...] = acc3[...] + jnp.where(mask, e3, zero)
            acc4[...] = acc4[...] + jnp.where(mask, e4, zero)
            return carry

        jax.lax.fori_loop(0, CHUNKS, body, 0)
        o_ref[0, 0, :] = (
            jnp.sum(acc1[...], axis=1) + jnp.sum(acc2[...], axis=1)
            + jnp.sum(acc3[...], axis=1) + jnp.sum(acc4[...], axis=1)
        )

    return kernel


def run(dtype, label):
    kern = make_kernel(dtype)
    call = pl.pallas_call(
        kern,
        grid=(NG,),
        in_specs=[
            pl.BlockSpec((1, 8, G), lambda g: (g, 0, 0)),
            pl.BlockSpec((CHUNKS, 8, 128), lambda g: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 8, G), lambda g: (g, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((NG, 8, G), jnp.float32),
        scratch_shapes=[pltpu.VMEM((G, 128), jnp.float32) for _ in range(4)],
    )
    i = jax.random.normal(jax.random.PRNGKey(0), (NG, 8, G), jnp.float32)
    i = i.at[:, 3].set(jnp.abs(i[:, 3]) + 0.5)
    j = jax.random.normal(jax.random.PRNGKey(1), (CHUNKS, 8, 128), jnp.float32)
    out = call(i, j)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = call(i, j)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / ITERS
    lanes = NG * G * CHUNKS * 128
    # ~60 castable flops + ~20 f32 flops per lane in this body
    print(f"{label:8s} {dt * 1e3:8.3f} ms   {lanes / dt / 1e12:.3f} Tlane/s")
    return dt


def main():
    print(f"backend={jax.default_backend()}  NG={NG} CHUNKS={CHUNKS}")
    f32 = run(jnp.float32, "f32")
    try:
        bf16 = run(jnp.bfloat16, "bf16")
        print(f"speedup bf16/f32: {f32 / bf16:.2f}x")
    except Exception as e:
        print(f"bf16 FAILED: {type(e).__name__}: {str(e)[:500]}")


if __name__ == "__main__":
    main()
