"""Conservation probes at the reference config (sedov 50^3, 200 steps).

Per-step energy-budget decomposition in f64 (host):

  A = d_ekin - dt*sum m a.v_mid     Press-scheme kinetic truncation
  B = d_eint - dt*sum m du          AB2 internal-energy correction term
  C = dt*(sum m du + sum m a.v_mid) force antisymmetry + v-centering

  d_etot(step) = A + B + C exactly (f64 identity on the f32 states).

v_mid = (v^n + v^{n+1})/2 with v^n re-ordered into the post-step sort
order via argsort of the pre-step keys (sedov box is periodic => the
in-step box is unchanged and the permutation reproducible).

P1 dt-scaling: 200-step drift with k_cour x {1.0, 0.5}: ratio ~2 =>
   first-order integrator loss; ~4 => second order; ~1 => dt-independent.

Usage: python scripts/probe_conservation.py [ve|std] [decomp|scale]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import dataclasses

import numpy as np
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.observables import conserved_quantities
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.simulation import Simulation

PROP = sys.argv[1] if len(sys.argv) > 1 else "ve"
MODE = sys.argv[2] if len(sys.argv) > 2 else "decomp"
STEPS = int(os.environ.get("PROBE_STEPS", "200"))
SIDE = int(os.environ.get("PROBE_SIDE", "50"))


def f64(a):
    return np.asarray(a, np.float64)


def energies(st, const):
    m = f64(st.m)
    ekin = 0.5 * np.sum(m * (f64(st.vx) ** 2 + f64(st.vy) ** 2
                             + f64(st.vz) ** 2))
    eint = np.sum(m * float(const.cv) * f64(st.temp))
    return ekin, eint


def decomp():
    state, box, const = init_sedov(SIDE)
    sim = Simulation(state, box, const, prop=PROP, block=8192,
                     check_every=1, keep_accels=True)
    probe_at = {60, 100, 140, 180}
    cum = dict(A=0.0, B=0.0, C=0.0)
    e0k, e0i = energies(sim.state, const)
    e0 = e0k + e0i
    for s in range(STEPS):
        st = sim.state
        keys = np.asarray(compute_sfc_keys(st.x, st.y, st.z, sim.box))
        order = np.argsort(keys, kind="stable")
        vxn, vyn, vzn = (f64(st.vx)[order], f64(st.vy)[order],
                         f64(st.vz)[order])
        ekin0, eint0 = energies(st, const)
        d = sim.step()
        st2 = sim.state
        if "ax" not in d:
            print("no accels in diag; keys:", sorted(d)); return
        dt = float(st2.min_dt)
        m = f64(st2.m)
        ax, ay, az = f64(d["ax"]), f64(d["ay"]), f64(d["az"])
        du = f64(st2.du)
        vmx = 0.5 * (vxn + f64(st2.vx))
        vmy = 0.5 * (vyn + f64(st2.vy))
        vmz = 0.5 * (vzn + f64(st2.vz))
        work = dt * np.sum(m * (ax * vmx + ay * vmy + az * vmz))
        heat = dt * np.sum(m * du)
        ekin1, eint1 = energies(st2, const)
        A = (ekin1 - ekin0) - work
        B = (eint1 - eint0) - heat
        C = heat + work
        for k, v in zip("ABC", (A, B, C)):
            cum[k] += v
        if s in probe_at or s == STEPS - 1:
            etot = ekin1 + eint1
            print(f"step {s:3d} dt={dt:.2e} drift={abs(etot-e0)/e0:.3e} "
                  f"A={cum['A']/e0:+.3e} B={cum['B']/e0:+.3e} "
                  f"C={cum['C']/e0:+.3e} "
                  f"(step: A={A/e0:+.2e} B={B/e0:+.2e} C={C/e0:+.2e})",
                  flush=True)


def scale():
    for ks in (1.0, 0.5):
        state, box, const = init_sedov(SIDE)
        const2 = dataclasses.replace(const, k_cour=const.k_cour * ks)
        sim = Simulation(state, box, const2, prop=PROP, block=8192,
                         check_every=10)
        e0 = float(conserved_quantities(sim.state, const2)["etot"])
        for _ in range(STEPS):
            sim.step()
        sim.flush()
        e1 = float(conserved_quantities(sim.state, const2)["etot"])
        print(f"[{PROP}] k_cour x{ks}: drift={abs(e1-e0)/abs(e0):.3e} "
              f"t={float(sim.state.ttot):.4f}", flush=True)


if __name__ == "__main__":
    decomp() if MODE == "decomp" else scale()
