"""Multi-chip comm-volume measurements (VERDICT r3 #6): Wmax vs S as
particles-per-shard grows, and bytes moved per exchange stage vs the
round-2 full-array replication baseline.

Size-based (no device timing): the windowed all_to_all moves
(P-1) * Wmax rows per shard per stage; replication moved S * (P-1);
the sparse per-cell exchange ships sum(hmax) — the same formulas the
runtime ``exchange`` telemetry events stamp (docs/OBSERVABILITY.md,
schema v2), so a run's events are checkable against this script.

Usage: JAX_PLATFORMS=cpu python scripts/measure_multichip.py
       [--quick] [--json]

``--json`` prints one bench-schema line ({"metric","value","unit",
"extra","manifest"}) — the shape ``sphexa-telemetry diff`` consumes
directly or buried in a ``MULTICHIP_r*.json`` wrapper's tail, giving
multi-chip comm regressions threshold exit codes in CI (the check.sh
full gate diffs a --quick run against MULTICHIP_BASELINE.json).
``--quick`` restricts to two small deterministic rows (no settling
step) so the gate stays cheap.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.parallel.exchange import estimate_halo_window
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.simulation import Simulation, make_propagator_config


def measure(side, P, settle=True):
    state, box, const = init_sedov(side)
    if settle and side < 120:
        # settle one step so the measured distribution is in-run, not the
        # raw lattice; at 4M+ a CPU step costs minutes and the lattice is
        # an adequate stand-in for the volume scaling
        sim = Simulation(state, box, const, prop="std", block=8192)
        sim.step()
        state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, keys, _ = _sort_by_keys(state, box, "hilbert")
    cfg = make_propagator_config(state, box, const, block=8192,
                                 backend="pallas")
    n = state.n
    S = -(-n // P)
    wmax = estimate_halo_window(state.x, state.y, state.z, state.h, keys,
                                box, cfg.nbr, P=P)
    # TRUE sparse halo need: distinct remote rows each dest requires
    # (what a per-cell halo exchange — the reference's exchangeHalos —
    # would move), vs the contiguous span the windowed design ships
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    ranges = group_cell_ranges(state.x, state.y, state.z, state.h, keys,
                               box, cfg.nbr)
    starts = np.asarray(ranges.starts)
    lens = np.asarray(ranges.lens)
    g = cfg.nbr.group
    ng = starts.shape[0]
    sparse = []
    for dest in range(P):
        g0, g1 = dest * S // g, min(((dest + 1) * S + g - 1) // g, ng)
        need = np.zeros(n, bool)
        for st, ln in zip(starts[g0:g1].ravel(), lens[g0:g1].ravel()):
            if ln > 0:
                need[st:st + ln] = True
        need[dest * S:(dest + 1) * S] = False  # own slab rows are local
        sparse.append(int(need.sum()))
    sparse_mean = float(np.mean(sparse))
    # bytes per shard per exchange stage: window rows x (P-1) peers x
    # fields x 4B. The std step exchanges 3 stage-sets (coords+h+m for
    # density: 4f; +vol for IAD: 4f; 17f for momentum); VE exchanges 6.
    # SHIPPED rows of the sparse per-cell exchange (the default path,
    # parallel/exchange.serve_sparse): sum of the sized per-distance
    # buffers — compare against the true sparse need above
    from sphexa_tpu.parallel.sizing import device_sparse_halo

    hcells = device_sparse_halo(state.x, state.y, state.z, state.h, keys,
                                box, cfg.nbr, P=P)
    win = (P - 1) * wmax
    rep = (P - 1) * S
    # gravity near field (the MAC-sized sparse serve, r13): per-dest
    # essential rows from the need matrix (what the Warren-Salmon LET
    # would ship) vs the retired full-slab exchange's (P-1)*S, plus the
    # per-distance cap fold the serve actually sizes its buffers from
    from sphexa_tpu.gravity.tree import linkage_from_leaves
    from sphexa_tpu.parallel.sizing import (
        gravity_need_matrix,
        leaf_array_from_device_keys,
    )

    leaf_tree = leaf_array_from_device_keys(keys, bucket_size=64)
    gtree, meta = linkage_from_leaves(leaf_tree, curve="hilbert")
    need = np.asarray(gravity_need_matrix(
        state.x, state.y, state.z, state.m, keys, box, gtree, meta,
        theta=0.5, P=P))
    grav_need = float((need.sum() - np.trace(need)) / P)
    j = np.arange(P)
    grav_shipped = int(sum(int(need[(j + r) % P, j].max())
                           for r in range(1, P)))
    return dict(n=n, S=S, wmax=wmax, ratio=wmax / S,
                win_rows=win, rep_rows=rep, saving=rep / max(win, 1),
                sparse=sparse_mean, sparse_frac=sparse_mean / S,
                shipped=sum(hcells), shipped_frac=sum(hcells) / S,
                grav_need=grav_need, grav_shipped=grav_shipped,
                grav_saving=rep / max(grav_need, 1.0))


#: the cheap deterministic rows of --quick mode: lattice state (no
#: settling step). side 16 = the dryrun scale sanity row; side 40 = the
#: first size whose sparse caps are genuinely partial on the lattice
#: (saving > 1 — the quantity the CI gate can actually see regress)
QUICK_CASES = ((16, 8), (40, 8))

FULL_CASES = ((16, 8), (24, 8), (32, 8), (48, 8), (64, 8),
              (80, 8), (160, 8), (160, 16),
              (48, 2), (48, 4), (48, 16))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="two small rows, no settling step (CI gate)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print one bench-schema JSON line for "
                         "sphexa-telemetry diff")
    args = ap.parse_args(argv)
    cases = QUICK_CASES if args.quick else FULL_CASES
    results = []
    if not args.as_json:
        print(f"{'side':>5} {'n':>9} {'P':>3} {'S':>8} {'Wmax':>7} "
              f"{'Wmax/S':>7} {'rows/stage':>11} {'vs repl':>8} "
              f"{'sparse':>8} {'sparse/S':>8} {'shipped':>8} {'ship/S':>7} "
              f"{'grav':>8} {'grav sv':>8}")
    for side, P in cases:
        try:
            r = measure(side, P, settle=not args.quick)
            results.append((side, P, r))
            if not args.as_json:
                print(f"{side:>5} {r['n']:>9} {P:>3} {r['S']:>8} "
                      f"{r['wmax']:>7} {r['ratio']:>7.3f} "
                      f"{r['win_rows']:>11} {r['saving']:>7.2f}x "
                      f"{r['sparse']:>8.0f} {r['sparse_frac']:>8.3f} "
                      f"{r['shipped']:>8} {r['shipped_frac']:>7.2f} "
                      f"{r['grav_need']:>8.0f} {r['grav_saving']:>7.2f}x",
                      flush=True)
        except Exception as e:
            print(f"{side:>5} P={P} FAILED: {type(e).__name__}: {e}"[:140],
                  file=sys.stderr, flush=True)
    if not args.as_json:
        return 0
    if not results:
        print("measure_multichip: every case failed", file=sys.stderr)
        return 1
    # headline: sparse-exchange saving vs full replication at the largest
    # measured row (higher is better — same diff direction as throughput);
    # per-row extras are flat numerics so `sphexa-telemetry diff` compares
    # them with the bench-vs-bench machinery
    side, P, head = results[-1]
    extra = {}
    for s, p, r in results:
        tag = f"s{s}_p{p}"
        extra[f"{tag}_shipped_rows"] = int(r["shipped"])
        extra[f"{tag}_shipped_frac"] = round(r["shipped_frac"], 4)
        extra[f"{tag}_sparse_frac"] = round(r["sparse_frac"], 4)
        extra[f"{tag}_wmax_frac"] = round(r["ratio"], 4)
        extra[f"{tag}_saving"] = round(r["rep_rows"] / max(r["shipped"], 1),
                                       4)
        extra[f"{tag}_grav_need_rows"] = round(r["grav_need"], 1)
        extra[f"{tag}_grav_shipped_rows"] = int(r["grav_shipped"])
        extra[f"{tag}_grav_saving"] = round(r["grav_saving"], 4)
    from sphexa_tpu.telemetry.manifest import build_manifest

    print(json.dumps({
        "metric": f"sparse-halo saving vs replication "
                  f"(sedov {side}^3, P={P})",
        "value": round(head["rep_rows"] / max(head["shipped"], 1), 4),
        "unit": "x",
        "extra": extra,
        "manifest": build_manifest(
            config={"quick": bool(args.quick),
                    "cases": [list(c) for c in cases]},
            particles=head["n"],
        ),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
