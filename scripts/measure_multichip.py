"""Multi-chip comm-volume measurements (VERDICT r3 #6): Wmax vs S as
particles-per-shard grows, and bytes moved per exchange stage vs the
round-2 full-array replication baseline.

Size-based (no device timing): the windowed all_to_all moves
(P-1) * Wmax rows per shard per stage; replication moved S * (P-1).

Usage: JAX_PLATFORMS=cpu python scripts/measure_multichip.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp

from sphexa_tpu.init import init_sedov
from sphexa_tpu.parallel.exchange import estimate_halo_window
from sphexa_tpu.propagator import _sort_by_keys
from sphexa_tpu.sfc.box import make_global_box
from sphexa_tpu.sfc.keys import compute_sfc_keys
from sphexa_tpu.simulation import Simulation, make_propagator_config


def measure(side, P):
    state, box, const = init_sedov(side)
    if side < 120:
        # settle one step so the measured distribution is in-run, not the
        # raw lattice; at 4M+ a CPU step costs minutes and the lattice is
        # an adequate stand-in for the volume scaling
        sim = Simulation(state, box, const, prop="std", block=8192)
        sim.step()
        state, box = sim.state, sim.box
    box = make_global_box(state.x, state.y, state.z, box)
    state, keys, _ = _sort_by_keys(state, box, "hilbert")
    cfg = make_propagator_config(state, box, const, block=8192,
                                 backend="pallas")
    n = state.n
    S = -(-n // P)
    wmax = estimate_halo_window(state.x, state.y, state.z, state.h, keys,
                                box, cfg.nbr, P=P)
    # TRUE sparse halo need: distinct remote rows each dest requires
    # (what a per-cell halo exchange — the reference's exchangeHalos —
    # would move), vs the contiguous span the windowed design ships
    from sphexa_tpu.sph.pallas_pairs import group_cell_ranges

    ranges = group_cell_ranges(state.x, state.y, state.z, state.h, keys,
                               box, cfg.nbr)
    starts = np.asarray(ranges.starts)
    lens = np.asarray(ranges.lens)
    g = cfg.nbr.group
    ng = starts.shape[0]
    sparse = []
    for dest in range(P):
        g0, g1 = dest * S // g, min(((dest + 1) * S + g - 1) // g, ng)
        need = np.zeros(n, bool)
        for st, ln in zip(starts[g0:g1].ravel(), lens[g0:g1].ravel()):
            if ln > 0:
                need[st:st + ln] = True
        need[dest * S:(dest + 1) * S] = False  # own slab rows are local
        sparse.append(int(need.sum()))
    sparse_mean = float(np.mean(sparse))
    # bytes per shard per exchange stage: window rows x (P-1) peers x
    # fields x 4B. The std step exchanges 3 stage-sets (coords+h+m for
    # density: 4f; +vol for IAD: 4f; 17f for momentum); VE exchanges 6.
    # SHIPPED rows of the sparse per-cell exchange (the default path,
    # parallel/exchange.serve_sparse): sum of the sized per-distance
    # buffers — compare against the true sparse need above
    from sphexa_tpu.parallel.sizing import device_sparse_halo

    hcells = device_sparse_halo(state.x, state.y, state.z, state.h, keys,
                                box, cfg.nbr, P=P)
    win = (P - 1) * wmax
    rep = (P - 1) * S
    return dict(n=n, S=S, wmax=wmax, ratio=wmax / S,
                win_rows=win, rep_rows=rep, saving=rep / max(win, 1),
                sparse=sparse_mean, sparse_frac=sparse_mean / S,
                shipped=sum(hcells), shipped_frac=sum(hcells) / S)


def main():
    print(f"{'side':>5} {'n':>9} {'P':>3} {'S':>8} {'Wmax':>7} "
          f"{'Wmax/S':>7} {'rows/stage':>11} {'vs repl':>8} "
          f"{'sparse':>8} {'sparse/S':>8} {'shipped':>8} {'ship/S':>7}")
    for side, P in ((16, 8), (24, 8), (32, 8), (48, 8), (64, 8),
                    (80, 8), (160, 8), (160, 16),
                    (48, 2), (48, 4), (48, 16)):
        try:
            r = measure(side, P)
            print(f"{side:>5} {r['n']:>9} {P:>3} {r['S']:>8} "
                  f"{r['wmax']:>7} {r['ratio']:>7.3f} "
                  f"{r['win_rows']:>11} {r['saving']:>7.2f}x "
                  f"{r['sparse']:>8.0f} {r['sparse_frac']:>8.3f} "
                  f"{r['shipped']:>8} {r['shipped_frac']:>7.2f}",
                  flush=True)
        except Exception as e:
            print(f"{side:>5} P={P} FAILED: {type(e).__name__}: {e}"[:140],
                  flush=True)


if __name__ == "__main__":
    main()
