"""Generate a relaxed glass template block and save it as HDF5.

The output file feeds the CLI's --glass flag (and the reference's
readTemplateBlock format). Usage:

    python scripts/make_glass.py [side=16] [relax_steps=40] [out=glass.h5]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    side = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    out = sys.argv[3] if len(sys.argv) > 3 else "glass.h5"

    from sphexa_tpu.init.glass import generate_glass_template, write_template_block

    x, y, z = generate_glass_template(side, steps)
    write_template_block(out, x, y, z)
    print(f"wrote {len(x)} glass particles to {out}")


if __name__ == "__main__":
    main()
