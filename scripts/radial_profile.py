#!/usr/bin/env python3
"""Radial profile of a dump field (reference scripts/radial_profile.py).

Usage: python scripts/radial_profile.py dump.h5 [-s STEP] [-f rho] [--bins N]
       python scripts/radial_profile.py dump.h5 --list

Prints a two-column (r, mean) table to stdout; pass --png out.png to plot
instead (matplotlib optional).
"""

import os
import sys
from argparse import ArgumentParser

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def list_steps(fname):
    import h5py

    with h5py.File(fname, "r") as f:
        print(f"{fname} contains the following steps:")
        print(f"{'hdf5 step':>12} {'iteration':>12} {'time':>15}")
        for k in sorted(
            (k for k in f.keys() if k.startswith("Step#")),
            key=lambda k: int(k.split("#")[1]),
        ):
            g = f[k]
            print(f"{k.split('#')[1]:>12} "
                  f"{int(np.asarray(g.attrs.get('iteration', 0))):>12} "
                  f"{float(np.asarray(g.attrs.get('time', 0.0))):>15.6g}")


def main(argv=None) -> int:
    ap = ArgumentParser()
    ap.add_argument("file")
    ap.add_argument("-s", "--step", type=int, default=-1)
    ap.add_argument("-f", "--field", default="rho")
    ap.add_argument("--bins", type=int, default=60)
    ap.add_argument("--png", default=None)
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        list_steps(args.file)
        return 0

    import h5py

    from sphexa_tpu.analysis.evrard import radial_profile

    with h5py.File(args.file, "r") as f:
        steps = sorted(
            (int(k.split("#")[1]) for k in f.keys() if k.startswith("Step#"))
        )
        step = steps[args.step] if args.step < 0 else args.step
        g = f[f"Step#{step}"]
        if args.field not in g:
            print(f"field {args.field!r} not in Step#{step}; available: "
                  f"{sorted(g.keys())}", file=sys.stderr)
            return 1
        x = np.asarray(g["x"])
        y = np.asarray(g["y"])
        z = np.asarray(g["z"])
        v = np.asarray(g[args.field])
        t = float(np.asarray(g.attrs.get("time", 0.0)))

    r = np.sqrt(x * x + y * y + z * z)
    prof = radial_profile(r, v, bins=args.bins)
    if args.png:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        plt.scatter(r, v, s=0.1, label="particles")
        plt.plot(prof["r"], prof["mean"], color="C1", label="binned mean")
        plt.xlabel("r")
        plt.ylabel(args.field)
        plt.title(f"{args.field} at t={t:.5g} (Step#{step})")
        plt.legend()
        plt.savefig(args.png)
        print(f"wrote {args.png}")
    else:
        print(f"# {args.field} radial profile, Step#{step}, t={t:.6g}")
        for rr, vv, cc in zip(prof["r"], prof["mean"], prof["count"]):
            if cc > 0:
                print(f"{rr:.6g} {vv:.6g}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
