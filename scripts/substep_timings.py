#!/usr/bin/env python3
"""Per-iteration timing breakdown from a --profile run
(reference scripts/substep_timings.py, stacked-bar phase plot).

Usage: python scripts/substep_timings.py profile.npz [--png out.png]

Without --png, prints per-phase totals/means; with it, draws the stacked
per-iteration bars.
"""

import sys
from argparse import ArgumentParser

import numpy as np


def main(argv=None) -> int:
    ap = ArgumentParser()
    ap.add_argument("file", nargs="?", default="profile.npz")
    ap.add_argument("--png", default=None)
    args = ap.parse_args(argv)

    data = np.load(args.file)
    phases = [k for k in data.files if k != "iteration"]
    iters = data["iteration"] if "iteration" in data.files else np.arange(
        len(data[phases[0]])
    )
    if not phases:
        print(f"{args.file} holds no phase series", file=sys.stderr)
        return 1

    if args.png:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        bottom = np.zeros(len(iters))
        for k in phases:
            v = np.nan_to_num(data[k])
            plt.bar(iters, v, bottom=bottom, label=k, width=1.0)
            bottom += v
        plt.xlabel("iteration")
        plt.ylabel("seconds")
        plt.legend()
        plt.title("per-iteration phase timings")
        plt.savefig(args.png, dpi=150)
        print(f"wrote {args.png}")
        return 0

    print(f"# {args.file}: {len(iters)} iterations")
    print(f"{'phase':>14} {'total[s]':>10} {'mean[ms]':>10} {'max[ms]':>10}")
    for k in phases:
        v = np.nan_to_num(data[k])
        print(f"{k:>14} {v.sum():>10.3f} {v.mean()*1e3:>10.2f} "
              f"{v.max()*1e3:>10.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
